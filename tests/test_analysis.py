"""scx-lint: every rule against its fixture corpus + the real tree.

The acceptance contract of the analysis subsystem:

- each SCX1xx rule fires on its known-bad fixture and stays silent on its
  known-clean twin;
- the ABI checker passes on the real native package and on the clean
  fixture pair, and catches every drift class on the bad pair — including
  a deliberately corrupted copy of the *real* bindings;
- the tsan.supp audit passes on the real suppression file and flags the
  bad fixture;
- each SCX4xx concurrency rule fires EXACTLY on its bad fixture's marked
  lines and stays silent on the clean twin; the real tree carries no
  unsuppressed SCX4xx finding, and its static lock graph names the
  library's witness-factory lock vocabulary;
- the runtime lock witness proxies record acquisition order, detect a
  constructed ABBA cycle and a static-graph divergence, and are a TRUE
  no-op (raw threading primitives) when SCTOOLS_TPU_LOCK_DEBUG is off;
- each SCX6xx frame-lifetime rule fires EXACTLY on its bad fixture's
  marked lines and stays silent on the clean twin; the real tree carries
  no unsuppressed SCX6xx finding; the ingest package is ownership-exempt
  (its runtime twin, the generation witness, is pinned in
  tests/test_ingest.py);
- the CLI exits 0 on the repository's own tree (the merge gate) and
  non-zero on the bad corpus.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from sctools_tpu.analysis import (
    audit_suppressions,
    build_aot_manifest,
    build_shape_contract,
    check_abi,
    check_aot,
    check_cost,
    check_life,
    check_mesh,
    check_races,
    check_shards,
    check_signatures,
    check_transfer_sites,
    contract_hash,
    dim_admissible,
    lint_file,
    lock_graph,
    transfer_inventory,
    validate_manifest,
)
from sctools_tpu.analysis import witness
from sctools_tpu.analysis.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures_scxlint")
JAXLINT = os.path.join(FIXTURES, "jaxlint")
ABI_CLEAN = os.path.join(FIXTURES, "abi", "clean")
ABI_BAD = os.path.join(FIXTURES, "abi", "bad")
SUPP = os.path.join(FIXTURES, "supp")
RACE = os.path.join(FIXTURES, "racecheck")
SHARD = os.path.join(FIXTURES, "shardcheck")
NATIVE = os.path.join(REPO, "sctools_tpu", "native")
TREE = [
    os.path.join(REPO, "sctools_tpu"),
    os.path.join(REPO, "bench.py"),
    os.path.join(REPO, "__graft_entry__.py"),
]

JAX_RULE_IDS = [f"SCX10{i}" for i in range(1, 10)] + [
    "SCX110", "SCX111", "SCX112", "SCX113", "SCX114", "SCX1001",
]


# --------------------------------------------------------------- jax lint

@pytest.mark.parametrize("rule", JAX_RULE_IDS)
def test_rule_fires_on_bad_fixture(rule):
    path = os.path.join(JAXLINT, f"{rule.lower()}_bad.py")
    findings = lint_file(path)
    assert findings, f"{rule} bad fixture produced no findings"
    assert {f.rule for f in findings} == {rule}
    assert all(f.line > 0 and f.path == path for f in findings)


@pytest.mark.parametrize("rule", JAX_RULE_IDS)
def test_rule_silent_on_clean_fixture(rule):
    # SCX106's negative fixture is a file *named* platform.py: the rule is
    # about ownership, not syntax
    name = "platform.py" if rule == "SCX106" else f"{rule.lower()}_clean.py"
    findings = lint_file(os.path.join(JAXLINT, name))
    assert findings == [], [f.render() for f in findings]


def test_scx112_ingest_dir_is_exempt(tmp_path):
    # SCX112 is about ownership: the scx-ingest subsystem IS the sanctioned
    # device_put site, wherever the repo checkout lives
    ingest_dir = tmp_path / "ingest"
    ingest_dir.mkdir()
    path = ingest_dir / "staging.py"
    path.write_text(
        "import jax\n\n\ndef up(value):\n    return jax.device_put(value)\n"
    )
    assert lint_file(str(path)) == []
    outside = tmp_path / "staging.py"
    outside.write_text(
        "import jax\n\n\ndef up(value):\n    return jax.device_put(value)\n"
    )
    findings = lint_file(str(outside))
    assert {f.rule for f in findings} == {"SCX112"}
    # only the IMMEDIATE parent confers ownership: a mere "ingest"
    # ancestor (e.g. a checkout cloned under ~/ingest/) must not disable
    # the rule
    nested = ingest_dir / "sub"
    nested.mkdir()
    deep = nested / "staging.py"
    deep.write_text(
        "import jax\n\n\ndef up(value):\n    return jax.device_put(value)\n"
    )
    findings = lint_file(str(deep))
    assert {f.rule for f in findings} == {"SCX112"}


def test_scx114_ingest_dir_is_exempt(tmp_path):
    # SCX114 is about ownership, like SCX112: ingest/ IS the sanctioned
    # pull site (wire.py implements the choke point)
    src = (
        "import jax\n\n\ndef down(value):\n    return jax.device_get(value)\n"
    )
    ingest_dir = tmp_path / "ingest"
    ingest_dir.mkdir()
    (ingest_dir / "wirelike.py").write_text(src)
    assert lint_file(str(ingest_dir / "wirelike.py")) == []
    (tmp_path / "wirelike.py").write_text(src)
    findings = lint_file(str(tmp_path / "wirelike.py"))
    assert {f.rule for f in findings} == {"SCX114"}
    # only the IMMEDIATE parent confers ownership (the SCX112 line)
    nested = ingest_dir / "sub"
    nested.mkdir()
    (nested / "wirelike.py").write_text(src)
    assert {f.rule for f in lint_file(str(nested / "wirelike.py"))} == {
        "SCX114"
    }


def test_scx1001_steer_dir_is_exempt(tmp_path):
    # SCX1001 is about ownership like SCX112: the steer package IS the
    # contract-checked apply path, wherever the checkout lives
    src = (
        "from sctools_tpu.utils.prefetch import set_depth_override\n\n\n"
        "def apply(depth):\n    set_depth_override(depth)\n"
    )
    steer_dir = tmp_path / "steer"
    steer_dir.mkdir()
    (steer_dir / "apply.py").write_text(src)
    assert lint_file(str(steer_dir / "apply.py")) == []
    (tmp_path / "apply.py").write_text(src)
    findings = lint_file(str(tmp_path / "apply.py"))
    assert {f.rule for f in findings} == {"SCX1001"}
    # only the IMMEDIATE parent confers ownership (the SCX112 line)
    nested = steer_dir / "sub"
    nested.mkdir()
    (nested / "apply.py").write_text(src)
    assert {f.rule for f in lint_file(str(nested / "apply.py"))} == {
        "SCX1001"
    }


def test_scx1001_knob_owners_are_exempt(tmp_path):
    # the modules that DEFINE the knobs stay lintable: prefetch.py hosts
    # the override cell, segments.py pins the floors
    (tmp_path / "prefetch.py").write_text(
        "_depth_override = None\n\n\ndef set_depth_override(depth):\n"
        "    global _depth_override\n    _depth_override = depth\n"
    )
    assert lint_file(str(tmp_path / "prefetch.py")) == []
    (tmp_path / "segments.py").write_text("RECORD_BUCKET_MIN = 4096\n")
    assert lint_file(str(tmp_path / "segments.py")) == []


def test_scx1001_real_tree_is_clean():
    # the live tree must only actuate knobs through steer/'s apply path;
    # a regression here means someone added an unguarded knob write
    for root in TREE:
        paths = []
        if os.path.isfile(root):
            paths = [root]
        else:
            for dirpath, _, names in os.walk(root):
                paths.extend(
                    os.path.join(dirpath, n)
                    for n in names if n.endswith(".py")
                )
        for path in paths:
            findings = [
                f for f in lint_file(path) if f.rule == "SCX1001"
            ]
            assert findings == [], [f.render() for f in findings]


def test_scx114_bad_fixture_marks_exact_lines():
    path = os.path.join(JAXLINT, "scx114_bad.py")
    with open(path, encoding="utf-8") as f:
        source = f.read()
    findings = lint_file(path)
    # one finding per offending construct: the two device_get forms, the
    # async kick, and the three tainted np.asarray/np.array pulls (the
    # import line additionally flags)
    lines = sorted({f.line for f in findings})
    assert len(lines) >= 6, [f.render() for f in findings]
    flagged_snippets = [
        source.splitlines()[line - 1] for line in lines
    ]
    for snippet in flagged_snippets:
        assert any(
            marker in snippet
            for marker in (
                "device_get", "copy_to_host_async", "np.asarray", "np.array",
            )
        ), snippet


def test_scx114_taint_is_per_scope(tmp_path):
    # a dispatch result tainting `out` in one function must not flag a
    # host-side np.asarray(out) in ANOTHER function
    src = (
        "import numpy as np\n"
        "from sctools_tpu.ops.counting import count_molecules\n\n\n"
        "def device_fn(cols, n):\n"
        "    out = count_molecules(cols, num_segments=n)\n"
        "    return out\n\n\n"
        "def host_fn(records):\n"
        "    out = list(records)\n"
        "    return np.asarray(out)\n"
    )
    path = tmp_path / "scoped.py"
    path.write_text(src)
    assert lint_file(str(path)) == [], [
        f.render() for f in lint_file(str(path))
    ]


def test_inline_and_file_suppressions():
    findings = lint_file(os.path.join(JAXLINT, "suppressed_bad.py"))
    assert findings == [], [f.render() for f in findings]


def test_suppression_is_rule_specific(tmp_path):
    # suppressing a DIFFERENT rule must not silence the finding
    src = (
        "# scx-lint: disable-file=SCX111\n"
        "import jax\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.sum().item()  # scx-lint: disable=SCX999\n"
    )
    path = tmp_path / "wrong_rule.py"
    path.write_text(src)
    findings = lint_file(str(path))
    assert [f.rule for f in findings] == ["SCX101"]


def test_import_jax_numpy_binds_root_package(tmp_path):
    # `import jax.numpy` binds the ROOT name: jax.jit must still be seen
    src = (
        "# scx-lint: disable-file=SCX111\n"
        "import jax.numpy\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.sum().item()\n"
    )
    path = tmp_path / "root_bind.py"
    path.write_text(src)
    assert [f.rule for f in lint_file(str(path))] == ["SCX101"]


def test_comment_above_decorator_suppresses_function_finding(tmp_path):
    src = (
        "# scx-lint: disable-file=SCX111\n"
        "import jax\n\n"
        "# scx-lint: disable=SCX103 -- shape param is deliberately traced\n"
        "@jax.jit\n"
        "def f(x, n_records):\n"
        "    return x[:n_records]\n"
    )
    path = tmp_path / "deco_supp.py"
    path.write_text(src)
    assert lint_file(str(path)) == []


def test_instrument_jit_is_a_traced_context(tmp_path):
    # the SCX111 shim must not blind the traced-context rules: a function
    # wrapped with xprof.instrument_jit still gets SCX101/SCX103 coverage
    # (and its static_argnames are honored), exactly as if it were jit
    src = (
        "import functools\n"
        "from sctools_tpu.obs import xprof\n\n"
        "@functools.partial(\n"
        "    xprof.instrument_jit, name='x', static_argnames=('kind',)\n"
        ")\n"
        "def f(x, kind, n_records):\n"
        "    return x[:n_records].sum().item()\n"
    )
    path = tmp_path / "instrumented.py"
    path.write_text(src)
    rules = sorted({f.rule for f in lint_file(str(path))})
    assert rules == ["SCX101", "SCX103"], rules
    # the `kind` static name is honored: no SCX103 about `kind`
    assert not any(
        "`kind`" in f.message for f in lint_file(str(path))
    )


def test_log_named_array_is_not_a_logging_call(tmp_path):
    src = (
        "# scx-lint: disable-file=SCX111\n"
        "import jax\n"
        "import jax.numpy as jnp\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    log = jnp.log(x)\n"
        "    return log.sum()\n"
    )
    path = tmp_path / "log_array.py"
    path.write_text(src)
    assert lint_file(str(path)) == []


def test_config_assignment_through_from_import(tmp_path):
    src = "from jax import config\nconfig.jax_enable_x64 = True\n"
    path = tmp_path / "cfg_assign.py"
    path.write_text(src)
    assert [f.rule for f in lint_file(str(path))] == ["SCX106"]


# ------------------------------------------------------------ ABI checker

def test_abi_clean_fixture():
    findings = check_abi(
        ABI_CLEAN, os.path.join(ABI_CLEAN, "bindings.py")
    )
    assert findings == [], [f.render() for f in findings]


def test_abi_bad_fixture_catches_every_drift_class():
    findings = check_abi(ABI_BAD, os.path.join(ABI_BAD, "bindings.py"))
    rules = sorted(f.rule for f in findings)
    # one of each drift class; scx_mangled is both unbound and mangled
    assert rules == [
        "SCX201", "SCX202", "SCX202", "SCX203", "SCX204", "SCX205", "SCX206",
    ]


def test_abi_real_tree_is_clean():
    findings = check_abi(NATIVE)
    assert findings == [], [f.render() for f in findings]


def _corrupt_real_bindings(tmp_path, old: str, new: str) -> str:
    source_path = os.path.join(NATIVE, "__init__.py")
    with open(source_path) as f:
        source = f.read()
    assert old in source, f"expected binding text changed: {old!r}"
    out = tmp_path / "corrupted_bindings.py"
    out.write_text(source.replace(old, new, 1))
    return str(out)


def test_abi_catches_corrupted_argtypes_entry(tmp_path):
    # narrow one 64-bit seed argument to 32 bits
    path = _corrupt_real_bindings(
        tmp_path, "ctypes.c_ulonglong", "ctypes.c_uint32"
    )
    findings = check_abi(NATIVE, path)
    assert any(
        f.rule == "SCX204" and "scx_synth_bam" in f.message for f in findings
    ), [f.render() for f in findings]


def test_abi_catches_dropped_argument(tmp_path):
    path = _corrupt_real_bindings(
        tmp_path,
        "lib.scx_stream_next.argtypes = [ctypes.c_void_p, ctypes.c_long]",
        "lib.scx_stream_next.argtypes = [ctypes.c_void_p]",
    )
    findings = check_abi(NATIVE, path)
    assert any(
        f.rule == "SCX203" and "scx_stream_next" in f.message
        for f in findings
    ), [f.render() for f in findings]


def test_abi_catches_corrupted_restype(tmp_path):
    path = _corrupt_real_bindings(
        tmp_path,
        "lib.scx_n_records.restype = ctypes.c_long",
        "lib.scx_n_records.restype = ctypes.c_int",
    )
    findings = check_abi(NATIVE, path)
    assert any(
        f.rule == "SCX205" and "scx_n_records" in f.message for f in findings
    ), [f.render() for f in findings]


def test_abi_brace_inside_string_literal(tmp_path):
    # a `{` inside a string literal must not truncate the extern "C" range
    (tmp_path / "fake.cpp").write_text(
        '#include <cstdio>\n'
        'extern "C" {\n'
        'long scx_lit(char* out, long n) {\n'
        '  return snprintf(out, n, "{\\"k\\": %ld}", n);\n'
        '}\n'
        'void scx_after(void* h) { (void)h; }\n'
        '}\n'
    )
    (tmp_path / "bindings.py").write_text(
        "import ctypes\n"
        "def bind(lib):\n"
        "    lib.scx_lit.restype = ctypes.c_long\n"
        "    lib.scx_lit.argtypes = [ctypes.c_char_p, ctypes.c_long]\n"
        "    lib.scx_after.restype = None\n"
        "    lib.scx_after.argtypes = [ctypes.c_void_p]\n"
    )
    findings = check_abi(str(tmp_path), str(tmp_path / "bindings.py"))
    assert findings == [], [f.render() for f in findings]


def test_abi_comment_marker_inside_string_literal(tmp_path):
    # a `//` inside a string literal is not a comment opener: the literal
    # (and everything after it) must keep parsing
    (tmp_path / "fake.cpp").write_text(
        'extern "C" {\n'
        'const char* scx_url(void* h) {\n'
        '  (void)h;\n'
        '  return "https://example.com/*not-a-comment*/";\n'
        '}\n'
        'void scx_after(void* h) { (void)h; }\n'
        '}\n'
    )
    (tmp_path / "bindings.py").write_text(
        "import ctypes\n"
        "def bind(lib):\n"
        "    lib.scx_url.restype = ctypes.c_char_p\n"
        "    lib.scx_url.argtypes = [ctypes.c_void_p]\n"
        "    lib.scx_after.restype = None\n"
        "    lib.scx_after.argtypes = [ctypes.c_void_p]\n"
    )
    findings = check_abi(str(tmp_path), str(tmp_path / "bindings.py"))
    assert findings == [], [f.render() for f in findings]


# ------------------------------------------------------------- supp audit

def test_supp_clean_fixture():
    findings = audit_suppressions(
        os.path.join(SUPP, "clean.supp"), ABI_CLEAN
    )
    assert findings == [], [f.render() for f in findings]


def test_supp_bad_fixture():
    findings = audit_suppressions(os.path.join(SUPP, "bad.supp"), ABI_CLEAN)
    assert sorted(f.rule for f in findings) == [
        "SCX301", "SCX301", "SCX301", "SCX302", "SCX303",
    ]


def test_supp_wildcard_matches_identifier_prefix(tmp_path):
    supp = tmp_path / "wild.supp"
    supp.write_text("race:scx_demo*\nrace:scx_nothing_like_this*\n")
    findings = audit_suppressions(str(supp), ABI_CLEAN)
    # the first entry prefixes real symbols; the second matches nothing
    assert [f.rule for f in findings] == ["SCX302"]
    assert findings[0].line == 2


def test_supp_real_tree_is_clean():
    findings = audit_suppressions(os.path.join(NATIVE, "tsan.supp"), NATIVE)
    assert findings == [], [f.render() for f in findings]


# --------------------------------------------------- scx-race (SCX4xx)

RACE_RULE_IDS = ["SCX401", "SCX402", "SCX403", "SCX404"]


def _marked_lines(path: str, rule: str) -> list:
    """Line numbers carrying the fixture's ``# <- SCXNNN`` markers."""
    with open(path, encoding="utf-8") as f:
        return [
            lineno
            for lineno, line in enumerate(f, start=1)
            if f"# <- {rule}" in line
        ]


@pytest.mark.parametrize("rule", RACE_RULE_IDS)
def test_race_rule_fires_exactly_on_marked_lines(rule):
    path = os.path.join(RACE, f"{rule.lower()}_bad.py")
    findings = check_races([path])
    assert findings, f"{rule} bad fixture produced no findings"
    assert {f.rule for f in findings} == {rule}
    expected = _marked_lines(path, rule)
    assert expected, f"fixture {path} has no # <- {rule} markers"
    assert sorted(f.line for f in findings) == expected, [
        f.render() for f in findings
    ]


@pytest.mark.parametrize("rule", RACE_RULE_IDS)
def test_race_rule_silent_on_clean_fixture(rule):
    findings = check_races(
        [os.path.join(RACE, f"{rule.lower()}_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_race_real_tree_is_clean():
    findings = check_races(
        [os.path.join(REPO, "sctools_tpu"), os.path.join(REPO, "bench.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_race_inline_suppression(tmp_path):
    src = (
        "import threading\n\n"
        "totals = {}\n\n\n"
        "def worker():\n"
        "    totals['k'] = 1  "
        "# scx-lint: disable=SCX403 -- benign monotonic flag\n\n\n"
        "def run():\n"
        "    t = threading.Thread(target=worker)\n"
        "    t.start()\n"
        "    totals['k'] = 2  "
        "# scx-lint: disable=SCX403 -- benign monotonic flag\n"
        "    t.join(timeout=1.0)\n"
    )
    path = tmp_path / "suppressed_race.py"
    path.write_text(src)
    assert check_races([str(path)]) == []


def test_race_bounded_acquire_is_not_a_death_path_finding(tmp_path):
    # a with-block acquisition NOT reachable from any death root stays
    # silent even though it is blocking
    src = (
        "import threading\n\n"
        "lock = threading.Lock()\n\n\n"
        "def ordinary():\n"
        "    with lock:\n"
        "        return 1\n"
    )
    path = tmp_path / "no_death_root.py"
    path.write_text(src)
    assert check_races([str(path)]) == []


def test_lock_graph_names_the_witness_vocabulary():
    graph = lock_graph([os.path.join(REPO, "sctools_tpu")])
    # every library lock is created through the witness factories with a
    # stable name — the vocabulary the runtime witness shares
    expected = {
        "obs.ring", "obs.sink", "obs.xprof", "guard.open_retries",
        "guard.degrade", "guard.quarantine", "guard.watchdog.deadline",
        "ingest.ring_state", "sched.faults", "sched.journal",
        "native.loader",
    }
    assert expected <= set(graph["locks"]), sorted(graph["locks"])
    # no derived-name stragglers: a raw threading.Lock() module global
    # would show up as <module>.<var>
    derived = {name for name in graph["locks"] if "sctools_tpu." in name}
    assert derived == set(), derived
    # the obs.enable() nesting (ring lock held across the sink attach) is
    # a structural edge every traced run reproduces — pin it
    edges = {(e["from"], e["to"]) for e in graph["edges"]}
    assert ("obs.ring", "obs.sink") in edges, sorted(edges)
    # the registered entry points include the SIGTERM flight recorder,
    # the scheduler heartbeat, the prefetch producer, and the watchdog
    kinds = {entry["kind"] for entry in graph["entries"]}
    assert {"signal", "thread", "timer", "provider"} <= kinds, kinds


def test_race_abba_is_rule_401_only():
    # the ABBA fixture must not double-report as 402/403/404
    findings = check_races([os.path.join(RACE, "scx401_bad.py")])
    assert {f.rule for f in findings} == {"SCX401"}


def test_race_sees_inside_match_case_bodies(tmp_path):
    # a blocking acquire inside a match-statement case on a signal path
    # must fire SCX402 and contribute its lock to the emitted graph
    src = (
        "import signal\n"
        "import threading\n\n"
        "lock = threading.Lock()\n\n\n"
        "def handler(signum, frame):\n"
        "    match signum:\n"
        "        case 15:\n"
        "            with lock:\n"
        "                pass\n\n\n"
        "signal.signal(signal.SIGTERM, handler)\n"
    )
    path = tmp_path / "match_death_path.py"
    path.write_text(src)
    findings = check_races([str(path)])
    assert [(f.rule, f.line) for f in findings] == [("SCX402", 10)]
    graph = lock_graph([str(path)])
    assert "match_death_path.lock" in graph["locks"]


def test_race_inventories_try_block_module_globals(tmp_path):
    # the try/except ImportError lock-declaration idiom still binds the
    # module namespace — an ABBA inversion over it must fire SCX401
    src = (
        "import threading\n\n"
        "try:\n"
        "    lock_a = threading.Lock()\n"
        "except Exception:\n"
        "    lock_a = None\n"
        "lock_b = threading.Lock()\n\n\n"
        "def path_one():\n"
        "    with lock_a:\n"
        "        with lock_b:\n"
        "            pass\n\n\n"
        "def path_two():\n"
        "    with lock_b:\n"
        "        with lock_a:\n"
        "            pass\n"
    )
    path = tmp_path / "try_global_lock.py"
    path.write_text(src)
    findings = check_races([str(path)])
    assert {f.rule for f in findings} == {"SCX401"}
    graph = lock_graph([str(path)])
    assert {"try_global_lock.lock_a", "try_global_lock.lock_b"} <= set(
        graph["locks"]
    )


def test_race_local_binding_shadows_module_global(tmp_path):
    # a thread target's own `totals = {}` makes its subscript write
    # purely local — it must not count as a cross-thread global write
    src = (
        "import threading\n\n"
        "totals = {}\n\n\n"
        "def worker():\n"
        "    totals = {}\n"
        "    totals['k'] = 1\n"
        "    return totals\n\n\n"
        "def main_path():\n"
        "    totals['k'] = 2\n\n\n"
        "t = threading.Thread(target=worker)\n"
    )
    path = tmp_path / "shadowed_global.py"
    path.write_text(src)
    assert check_races([str(path)]) == []


def test_race_keyword_nonblocking_probe_is_bounded(tmp_path):
    # lock.acquire(blocking=False) is the readable spelling of the
    # sanctioned non-blocking death-path probe — not an SCX402
    src = (
        "import signal\n"
        "import threading\n\n"
        "lock = threading.Lock()\n\n\n"
        "def handler(signum, frame):\n"
        "    got = lock.acquire(blocking=False)\n"
        "    if got:\n"
        "        lock.release()\n\n\n"
        "signal.signal(signal.SIGTERM, handler)\n"
    )
    path = tmp_path / "keyword_probe.py"
    path.write_text(src)
    assert check_races([str(path)]) == []


def test_race_enclosing_scope_binding_shadows_global(tmp_path):
    # a closure writes the ENCLOSING function's local, not the module
    # global — the shadow walk must follow the parent chain the same
    # way lock resolution does
    src = (
        "import threading\n\n"
        "totals = {}\n\n\n"
        "def run():\n"
        "    totals = {}\n\n"
        "    def worker():\n"
        "        totals['k'] = 1\n\n"
        "    t = threading.Thread(target=worker)\n"
        "    t.start()\n"
        "    totals['k'] = 2\n"
        "    t.join(timeout=1.0)\n"
    )
    path = tmp_path / "closure_shadow.py"
    path.write_text(src)
    assert check_races([str(path)]) == []


def test_race_positional_thread_target_registers_entry(tmp_path):
    # threading.Thread(None, worker) — positional target — must create
    # the same entry root as target=worker
    src = (
        "import threading\n\n"
        "totals = {}\n\n\n"
        "def worker():\n"
        "    totals['k'] = 1\n\n\n"
        "def run():\n"
        "    t = threading.Thread(None, worker)\n"
        "    t.start()\n"
        "    totals['k'] = 2\n"
        "    t.join(timeout=1.0)\n"
    )
    path = tmp_path / "positional_target.py"
    path.write_text(src)
    findings = check_races([str(path)])
    assert {f.rule for f in findings} == {"SCX403"}, [
        f.render() for f in findings
    ]
    graph = lock_graph([str(path)])
    assert any(
        entry["kind"] == "thread" for entry in graph["entries"]
    ), graph["entries"]


# --------------------------------------------------- scx-shard (SCX5xx)

SHARD_RULE_IDS = ["SCX501", "SCX502", "SCX503", "SCX504", "SCX505"]


@pytest.mark.parametrize("rule", SHARD_RULE_IDS)
def test_shard_rule_fires_exactly_on_marked_lines(rule):
    path = os.path.join(SHARD, f"{rule.lower()}_bad.py")
    findings = check_shards([path])
    assert findings, f"{rule} bad fixture produced no findings"
    assert {f.rule for f in findings} == {rule}
    expected = _marked_lines(path, rule)
    assert expected, f"fixture {path} has no # <- {rule} markers"
    assert sorted(f.line for f in findings) == expected, [
        f.render() for f in findings
    ]


@pytest.mark.parametrize("rule", SHARD_RULE_IDS)
def test_shard_rule_silent_on_clean_fixture(rule):
    findings = check_shards(
        [os.path.join(SHARD, f"{rule.lower()}_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_shard_real_tree_is_clean():
    # audited inline suppressions allowed (each carries a justification);
    # anything else is a merge blocker, same contract as make shardcheck
    findings = check_shards(TREE)
    assert findings == [], [f.render() for f in findings]


def test_shard_inline_suppression(tmp_path):
    src = (
        "import functools\n\n"
        "from sctools_tpu.obs.xprof import instrument_jit\n\n\n"
        "@functools.partial(\n"
        "    instrument_jit, name='t.kernel', static_argnames=('n',)\n"
        ")\n"
        "def kernel(cols, n):\n"
        "    return cols\n\n\n"
        "def dispatch(frame):\n"
        "    n = len(frame)\n"
        "    return kernel(frame, n=n)  "
        "# scx-lint: disable=SCX503 -- bucketed upstream by construction\n"
    )
    path = tmp_path / "suppressed_shard.py"
    path.write_text(src)
    assert check_shards([str(path)]) == []


def test_shard_taint_cleared_by_reassignment(tmp_path):
    # a name rebound to a shape-independent value is no longer tainted
    src = (
        "import functools\n\n"
        "from sctools_tpu.obs.xprof import instrument_jit\n\n\n"
        "@functools.partial(\n"
        "    instrument_jit, name='t.kernel', static_argnames=('n',)\n"
        ")\n"
        "def kernel(cols, n):\n"
        "    return cols\n\n\n"
        "def dispatch(frame):\n"
        "    n = len(frame)\n"
        "    n = 4096\n"
        "    return kernel(frame, n=n)\n"
    )
    path = tmp_path / "retainted_shard.py"
    path.write_text(src)
    assert check_shards([str(path)]) == []


# ------------------------------------------------- shape contract (witness)

def test_contract_models_the_real_tree():
    contract = build_shape_contract(TREE)
    sites = contract["sites"]
    for needed in (
        "metrics.compute_entity_metrics",
        "metrics.compact_results_wire",
        "ops.count_molecules",
        "parallel.sharded_metrics",
    ):
        assert needed in sites, sorted(sites)
    # the mesh axis universe carries the library's axis vocabulary
    assert "shard" in contract["axis_universe"]
    # the streaming sites are recognized as bucketed (their dispatchers
    # reach a bucket/pad helper), so raw dims are rejected there
    assert sites["metrics.compute_entity_metrics"]["dims"] == "bucketed"
    # the sharded merge site is marked sharded (its specs are symbolic —
    # P(axis_name) — so per-site axes stay empty and the observed axis
    # names validate against the global universe instead)
    assert sites["parallel.sharded_metrics"]["sharded"] is True
    assert set(sites["parallel.sharded_metrics"]["axes"]) <= set(
        contract["axis_universe"]
    )
    assert 4096 in contract["bucket_minimums"]


def test_contract_closed_over_bucket_universe():
    # the property the smokes rely on: EVERY size the bucket tables can
    # emit, for every literal minimum the package uses, is admitted —
    # a legal dispatch can never fail the runtime witness
    from sctools_tpu.ops.segments import bucket_size

    contract = build_shape_contract(TREE)
    ns = (
        list(range(1, 300))
        + [1000, 4095, 4096, 4097, 12345, 1 << 17, (1 << 20) + 7]
    )
    for minimum in contract["bucket_minimums"]:
        for n in ns:
            dim = bucket_size(n, minimum=minimum)
            assert dim_admissible(dim, contract), (minimum, n, dim)


def test_contract_closed_over_wire_universe():
    # monoblock wire lengths: every (schema variant, padded bucket,
    # run-table bucket) combination the packer can produce is admitted
    from sctools_tpu.io.packed import wire_layout

    contract = build_shape_contract(TREE)
    for wide in (False, True):
        for small in (False, True):
            for run_keys in (False, True):
                for with_cb in (False, True):
                    widths = sum(
                        w for _, w in wire_layout(
                            wide, small, run_keys=run_keys, with_cb=with_cb
                        )
                    )
                    runs_options = [0] if not run_keys else [4096, 1 << 16]
                    for exp in range(12, 21):
                        padded = 1 << exp
                        for runs in runs_options:
                            dim = 1 + padded * widths // 4 + 2 * runs
                            assert dim_admissible(dim, contract), (
                                wide, small, run_keys, with_cb, padded,
                                runs, dim,
                            )


def test_dim_admissible_rejects_raw_sizes():
    contract = build_shape_contract(TREE)
    for raw in (300, 4097, 5000, 12345, 999_999):
        assert not dim_admissible(raw, contract), raw
    for legal in (0, 1, 37, 256, 4096, 8192, 1 << 20):
        assert dim_admissible(legal, contract), legal


def _toy_contract():
    return {
        "version": 1,
        "axis_universe": ["shard"],
        "bucket_minimums": [4096],
        "pad_multiples": [],
        "pow2_min": 8,
        "small_dim_max": 256,
        "wire": {
            "header_words": 1, "run_table_lanes": 2,
            "min_record_bytes": 12, "max_record_bytes": 72,
        },
        "sites": {
            "m.kernel": {
                "module": "m", "kind": "jit",
                "static_argnames": ["kind", "k"],
                "dims": "bucketed",
                "statics": {
                    "kind": {"open": False, "values": ["'cell'", "'gene'"]},
                    "k": {"open": True, "values": []},
                },
                "sharded": False, "axes": [],
            },
            "m.sharded": {
                "module": "m", "kind": "shard_map", "static_argnames": [],
                "dims": "any", "statics": {},
                "sharded": True, "axes": ["shard"],
            },
        },
    }


def test_signatures_subset_accepts_legal_observations():
    sites = {
        "m.kernel": {
            "signatures": {"(int32[4096,16]) {k=8192, kind='cell'}": 1}
        },
        "m.sharded": {"signatures": {"(float32[2,4096]@(shard))": 1}},
        "m.idle": {"signatures": {}},  # declared-but-never-ran: skipped
    }
    assert check_signatures(_toy_contract(), sites) == []


def test_signatures_reject_unknown_site():
    sites = {"m.rogue": {"signatures": {"(int32[4096])": 1}}}
    violations = check_signatures(_toy_contract(), sites)
    assert len(violations) == 1 and "not present" in violations[0]


def test_signatures_reject_raw_dim_at_bucketed_site():
    sites = {"m.kernel": {"signatures": {"(int32[12345]) {kind='cell'}": 1}}}
    violations = check_signatures(_toy_contract(), sites)
    assert violations and "12345" in violations[0]


def test_signatures_accept_raw_dim_at_any_site():
    sites = {"m.sharded": {"signatures": {"(int32[12345]@(shard))": 1}}}
    assert check_signatures(_toy_contract(), sites) == []


def test_signatures_reject_undeclared_axis():
    sites = {"m.sharded": {"signatures": {"(int32[4096]@(rows))": 1}}}
    violations = check_signatures(_toy_contract(), sites)
    assert violations and "rows" in violations[0]


def test_signatures_reject_sharded_operand_at_unsharded_site():
    sites = {
        "m.kernel": {"signatures": {"(int32[4096]@(shard)) {kind='cell'}": 1}}
    }
    violations = check_signatures(_toy_contract(), sites)
    assert violations and "non-shard_map" in violations[0]


def test_signatures_reject_static_outside_closed_universe():
    sites = {"m.kernel": {"signatures": {"(int32[4096]) {kind='umi'}": 1}}}
    violations = check_signatures(_toy_contract(), sites)
    assert violations and "kind" in violations[0]


def test_signatures_reject_raw_open_static_int():
    sites = {"m.kernel": {"signatures": {"(int32[4096]) {k=5000}": 1}}}
    violations = check_signatures(_toy_contract(), sites)
    assert violations and "k=5000" in violations[0]


def test_signatures_reject_undeclared_static_name():
    sites = {"m.kernel": {"signatures": {"(int32[4096]) {rows=4096}": 1}}}
    violations = check_signatures(_toy_contract(), sites)
    assert violations and "rows" in violations[0]


def test_signatures_flag_overflow_marker_as_lost_coverage():
    # >64 distinct signatures at one site collapses into the registry's
    # overflow bucket — the exact signatures are gone, so the subset
    # check cannot vouch for them, and that many signatures IS the
    # shape-flapping regression this gate exists to catch
    sites = {"m.kernel": {"signatures": {"(other signatures)": 3}}}
    violations = check_signatures(_toy_contract(), sites)
    assert len(violations) == 1 and "overflow" in violations[0]


def test_contract_records_aliased_bucket_minimums(tmp_path):
    src = (
        "from sctools_tpu.ops.segments import bucket_size as bs\n\n\n"
        "def dispatch(frame):\n"
        "    return bs(len(frame), minimum=512)\n"
    )
    path = tmp_path / "aliased_bucket.py"
    path.write_text(src)
    contract = build_shape_contract([str(path)])
    assert 512 in contract["bucket_minimums"]


# ------------------------------------------------- runtime lock witness

@pytest.fixture
def lock_debug(monkeypatch):
    monkeypatch.setenv("SCTOOLS_TPU_LOCK_DEBUG", "1")
    monkeypatch.delenv("SCTOOLS_TPU_LOCK_GRAPH", raising=False)
    witness.reset()
    yield
    witness.reset()


def test_witness_off_is_a_true_noop(monkeypatch):
    # off (unset or =0) must hand back the RAW threading primitives —
    # not a proxy, not a subclass: zero overhead on the hot path (the
    # bench.py guard_overhead leg asserts the same on the live library)
    for value in (None, "0"):
        if value is None:
            monkeypatch.delenv("SCTOOLS_TPU_LOCK_DEBUG", raising=False)
        else:
            monkeypatch.setenv("SCTOOLS_TPU_LOCK_DEBUG", value)
        lock = witness.make_lock("test.noop")
        rlock = witness.make_rlock("test.noop_r")
        assert type(lock) is type(threading.Lock()), type(lock)
        assert type(rlock) is type(threading.RLock()), type(rlock)
        assert not isinstance(lock, witness.WitnessLock)


def test_witness_records_order_edges(lock_debug):
    a = witness.make_lock("test.a")
    b = witness.make_lock("test.b")
    assert isinstance(a, witness.WitnessLock)
    with a:
        with b:
            pass
    edges = witness.observed_edges()
    assert ("test.a", "test.b") in edges
    assert edges[("test.a", "test.b")]["count"] == 1
    assert witness.acquire_counts() == {"test.a": 1, "test.b": 1}
    assert witness.violations() == []


def test_witness_cross_thread_release_leaves_no_stale_entry(lock_debug):
    # threading.Lock permits release from a thread other than the
    # acquirer (handoff); the held entry must leave the ACQUIRER's
    # stack, or its next acquisition mints a phantom order edge
    handoff = witness.make_lock("test.handoff")
    victim = witness.make_lock("test.handoff_victim")
    acquired = threading.Event()
    released = threading.Event()

    def worker():
        handoff.acquire()
        acquired.set()
        released.wait(timeout=5)
        with victim:  # after the handoff: this thread holds NOTHING
            pass

    t = threading.Thread(target=worker)
    t.start()
    assert acquired.wait(timeout=5)
    handoff.release()  # cross-thread release on the main thread
    released.set()
    t.join(timeout=5)
    edges = witness.observed_edges()
    assert ("test.handoff", "test.handoff_victim") not in edges, edges
    assert witness.violations() == []


def test_witness_detects_constructed_abba_cycle(lock_debug):
    a = witness.make_lock("test.cycle_a")
    b = witness.make_lock("test.cycle_b")
    with a:
        with b:
            pass
    # the reverse interleaving closes the cycle (single-threaded is
    # enough: the order graph is about edges, not liveness)
    with b:
        with a:
            pass
    kinds = [v["kind"] for v in witness.violations()]
    assert "cycle" in kinds, witness.violations()


def test_witness_flags_edges_unknown_to_the_static_graph(
    lock_debug, tmp_path, monkeypatch
):
    graph_path = tmp_path / "graph.json"
    graph_path.write_text(
        json.dumps({"edges": [{"from": "test.g_a", "to": "test.g_b"}]})
    )
    monkeypatch.setenv("SCTOOLS_TPU_LOCK_GRAPH", str(graph_path))
    a = witness.make_lock("test.g_a")
    b = witness.make_lock("test.g_b")
    c = witness.make_lock("test.g_c")
    with a:
        with b:  # known edge: no violation
            pass
    assert witness.violations() == []
    with a:
        with c:  # edge absent from the static model: the model lied
            pass
    kinds = [v["kind"] for v in witness.violations()]
    assert kinds == ["unknown-edge"], witness.violations()


def test_witness_bounded_acquire_is_exempt_from_order_checks(
    lock_debug, tmp_path, monkeypatch
):
    # bounded acquires are the SANCTIONED death-path pattern: a signal
    # handler's flight dump bounded-acquires under whatever locks the
    # interrupted thread held, which no static model can enumerate —
    # recorded for diagnosis, but neither the cycle nor the
    # static-graph check applies (the static SCX401 line)
    graph_path = tmp_path / "graph.json"
    graph_path.write_text(json.dumps({"edges": []}))
    monkeypatch.setenv("SCTOOLS_TPU_LOCK_GRAPH", str(graph_path))
    a = witness.make_lock("test.bnd_a")
    b = witness.make_lock("test.bnd_b")
    with a:
        assert b.acquire(timeout=0.5)
        b.release()
    with b:
        assert a.acquire(timeout=0.5)  # would close a cycle if counted
        a.release()
    assert witness.violations() == []
    edges = witness.observed_edges()
    assert edges[("test.bnd_a", "test.bnd_b")]["bounded"] is True
    assert edges[("test.bnd_b", "test.bnd_a")]["bounded"] is True
    # first BLOCKING observation of a so-far-bounded edge: it now
    # participates in deadlock analysis and faces the skipped checks
    with a:
        with b:
            pass
    kinds = [v["kind"] for v in witness.violations()]
    assert kinds == ["unknown-edge"], witness.violations()


def test_witness_rlock_reentry_is_not_an_edge(lock_debug):
    r = witness.make_rlock("test.reentrant")
    with r:
        with r:
            pass
    assert witness.observed_edges() == {}
    assert witness.acquire_counts() == {"test.reentrant": 2}


def test_witness_stall_records_violation_then_acquires(
    lock_debug, monkeypatch
):
    monkeypatch.setenv("SCTOOLS_TPU_LOCK_DEBUG_STALL_S", "0.05")
    lock = witness.make_lock("test.stall")
    release = threading.Event()

    def holder():
        lock.acquire()
        release.wait(timeout=10.0)
        lock.release()

    thread = threading.Thread(target=holder, daemon=True)
    thread.start()
    # let the holder win the lock, then unblock it shortly after the
    # stall threshold has fired on our blocking acquire
    deadline_timer = threading.Timer(0.3, release.set)
    deadline_timer.start()
    try:
        assert lock.acquire() is True  # blocks past the 0.05 s threshold
        lock.release()
    finally:
        release.set()
        thread.join(timeout=10.0)
        deadline_timer.cancel()
    kinds = [v["kind"] for v in witness.violations()]
    assert "stall" in kinds, witness.violations()


def test_witness_dump_roundtrip(lock_debug, tmp_path):
    a = witness.make_lock("test.dump_a")
    b = witness.make_lock("test.dump_b")
    with a:
        with b:
            pass
    target = tmp_path / "locks.json"
    assert witness.dump(str(target)) == str(target)
    data = json.loads(target.read_text())
    assert data["enabled"] is True
    assert {(e["from"], e["to"]) for e in data["edges"]} == {
        ("test.dump_a", "test.dump_b")
    }
    assert data["violations"] == []
    assert data["acquires"] == {"test.dump_a": 1, "test.dump_b": 1}


# -------------------------------------------------------------------- CLI

def test_cli_repo_tree_is_clean(capsys):
    rc = cli_main([os.path.join(REPO, "sctools_tpu")])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 finding(s)" in out


def test_cli_bad_corpus_fails(capsys):
    rc = cli_main(["-q", JAXLINT])
    out = capsys.readouterr().out
    assert rc == 1
    assert "SCX101" in out and "SCX108" in out


def test_cli_native_dir_flag(capsys):
    rc = cli_main(
        ["-q", "--no-jax-lint", "--no-supp", "--native-dir", NATIVE,
         os.path.join(REPO, "sctools_tpu")]
    )
    assert rc == 0, capsys.readouterr().out


def test_cli_module_invocation():
    result = subprocess.run(
        [sys.executable, "-m", "sctools_tpu.analysis", "--list-rules"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert result.returncode == 0, result.stderr
    assert "SCX101" in result.stdout and "SCX303" in result.stdout
    assert "SCX404" in result.stdout and "SCX505" in result.stdout
    assert "SCX605" in result.stdout


def test_cli_race_only(capsys):
    rc = cli_main(
        ["--race-only", os.path.join(REPO, "sctools_tpu"),
         os.path.join(REPO, "bench.py")]
    )
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "passes: race" in out


def test_cli_race_only_fails_on_bad_corpus(capsys):
    rc = cli_main(["-q", "--race-only", RACE])
    out = capsys.readouterr().out
    assert rc == 1
    for rule in RACE_RULE_IDS:
        assert rule in out, (rule, out)


def test_cli_emit_lock_graph(tmp_path, capsys):
    target = tmp_path / "graph.json"
    rc = cli_main(
        ["--emit-lock-graph", str(target),
         os.path.join(REPO, "sctools_tpu")]
    )
    assert rc == 0, capsys.readouterr().out
    graph = json.loads(target.read_text())
    assert graph["version"] == 1
    assert "obs.ring" in graph["locks"]
    assert graph["edges"] and graph["entries"]


def test_cli_shard_only(capsys):
    rc = cli_main(["--shard-only"] + TREE)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "passes: shard" in out


def test_cli_shard_only_fails_on_bad_corpus(capsys):
    rc = cli_main(["-q", "--shard-only", SHARD])
    out = capsys.readouterr().out
    assert rc == 1
    for rule in SHARD_RULE_IDS:
        assert rule in out, (rule, out)


def test_cli_race_and_shard_only_compose(capsys):
    # the `make modelcheck` shape: both whole-package passes, one process
    rc = cli_main(["--race-only", "--shard-only", RACE, SHARD])
    out = capsys.readouterr().out
    assert rc == 1
    assert "SCX401" in out and "SCX501" in out
    assert "passes: race, shard" in out


def test_cli_emit_shape_contract(tmp_path, capsys):
    target = tmp_path / "contract.json"
    rc = cli_main(["--emit-shape-contract", str(target)] + TREE)
    assert rc == 0, capsys.readouterr().out
    contract = json.loads(target.read_text())
    assert contract["version"] == 1
    assert "shard" in contract["axis_universe"]
    assert "metrics.compute_entity_metrics" in contract["sites"]


def test_cli_json_findings_cover_all_passes(capsys):
    # one machine-readable array across passes (racecheck + shardcheck)
    rc = cli_main(["--json", "--race-only", "--shard-only", RACE, SHARD])
    out = capsys.readouterr().out
    assert rc == 1
    payload = json.loads(out)
    rules = {f["rule"] for f in payload["findings"]}
    assert {"SCX401", "SCX501", "SCX505"} <= rules, rules
    for finding in payload["findings"]:
        assert finding["path"] and finding["line"] > 0 and finding["message"]
    assert payload["checked_files"] > 0


def test_cli_json_clean_tree_is_empty(capsys):
    rc = cli_main(["--json", "--shard-only"] + TREE)
    out = capsys.readouterr().out
    assert rc == 0
    assert json.loads(out)["findings"] == []


# ----------------------------------------------------- lifecheck (SCX6xx)

LIFE = os.path.join(FIXTURES, "lifecheck")
LIFE_RULE_IDS = ["SCX601", "SCX602", "SCX603", "SCX604", "SCX605"]


@pytest.mark.parametrize("rule", LIFE_RULE_IDS)
def test_life_rule_fires_exactly_on_marked_lines(rule):
    path = os.path.join(LIFE, f"{rule.lower()}_bad.py")
    findings = check_life([path])
    assert findings, f"{rule} bad fixture produced no findings"
    assert {f.rule for f in findings} == {rule}
    expected = _marked_lines(path, rule)
    assert expected, f"fixture {path} has no # <- {rule} markers"
    assert sorted(f.line for f in findings) == expected, [
        f.render() for f in findings
    ]


@pytest.mark.parametrize("rule", LIFE_RULE_IDS)
def test_life_rule_silent_on_clean_fixture(rule):
    findings = check_life(
        [os.path.join(LIFE, f"{rule.lower()}_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_life_real_tree_is_clean():
    # the audit contract: every SCX601-605 finding on the real tree is
    # fixed or carries a justified inline suppression — currently zero of
    # either, and this pin keeps it that way
    findings = check_life(TREE)
    assert findings == [], [f.render() for f in findings]


def test_life_inline_suppression(tmp_path):
    src = (
        "from sctools_tpu.ingest import ring_frames\n\n\n"
        "class Holder:\n"
        "    def __init__(self):\n"
        "        self.last = None\n\n"
        "    def consume(self, bam):\n"
        "        for frame in ring_frames(bam, 4096):\n"
        "            self.last = frame  "
        "# scx-lint: disable=SCX601 -- single-batch tool, ring exhausted\n"
    )
    path = tmp_path / "suppressed_life.py"
    path.write_text(src)
    assert check_life([str(path)]) == []


def test_life_ingest_dir_is_exempt(tmp_path):
    # the ingest package OWNS the buffer lifecycle (arena recycling, the
    # slot budget, the generation witness): its own view handling is the
    # mechanism, not a violation — the same immediate-parent ownership
    # line SCX112/SCX113 draw
    src = (
        "from sctools_tpu.ingest import ring_frames\n\n\n"
        "class Holder:\n"
        "    def __init__(self):\n"
        "        self.last = None\n\n"
        "    def consume(self, bam):\n"
        "        for frame in ring_frames(bam, 4096):\n"
        "            self.last = frame\n"
    )
    ingest_dir = tmp_path / "ingest"
    ingest_dir.mkdir()
    (ingest_dir / "staging.py").write_text(src)
    assert check_life([str(ingest_dir / "staging.py")]) == []
    outside = tmp_path / "staging.py"
    outside.write_text(src)
    findings = check_life([str(outside)])
    assert {f.rule for f in findings} == {"SCX601"}
    # only the IMMEDIATE parent confers ownership
    nested = ingest_dir / "sub"
    nested.mkdir()
    (nested / "staging.py").write_text(src)
    findings = check_life([str(nested / "staging.py")])
    assert {f.rule for f in findings} == {"SCX601"}


def test_life_frame_iter_taint_crosses_calls(tmp_path):
    # the gatherer pattern: ring_frames() is consumed by a helper the
    # iterable is PASSED to — the consumer loop lives in the callee, so
    # frame-source-ness must follow the argument through the call graph
    src = (
        "from sctools_tpu.ingest import ring_frames\n\n\n"
        "class Pipeline:\n"
        "    def __init__(self):\n"
        "        self.tail = None\n\n"
        "    def run(self, bam):\n"
        "        frames = ring_frames(bam, 4096)\n"
        "        self._drain(frames)\n\n"
        "    def _drain(self, frames):\n"
        "        for frame in frames:\n"
        "            self.tail = frame\n"
    )
    path = tmp_path / "taint_life.py"
    path.write_text(src)
    findings = check_life([str(path)])
    assert [(f.rule, f.line) for f in findings] == [("SCX601", 14)], [
        f.render() for f in findings
    ]


def test_life_copy_launders_the_carry(tmp_path):
    # an uncopied cross-iteration carry overflows the window; the same
    # loop with copy_frame on the carry is inside it
    bad = (
        "from sctools_tpu.ingest import ring_frames\n\n\n"
        "def consume(bam):\n"
        "    frames = ring_frames(bam, 4096)\n"
        "    it = iter(frames)\n"
        "    prev = None\n"
        "    for frame in frames:\n"
        "        look = next(it, None)\n"
        "        if prev is not None:\n"
        "            print(prev.n_records)\n"
        "        prev = frame\n"
    )
    path = tmp_path / "overflow_life.py"
    path.write_text(bad)
    assert {f.rule for f in check_life([str(path)])} == {"SCX602"}
    good = bad.replace(
        "from sctools_tpu.ingest import ring_frames\n",
        "from sctools_tpu.ingest import ring_frames\n"
        "from sctools_tpu.io.packed import copy_frame\n",
    ).replace("prev = frame\n", "prev = copy_frame(frame)\n")
    path.write_text(good)
    assert check_life([str(path)]) == []


def test_cli_life_only(capsys):
    rc = cli_main(["--life-only"] + TREE)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "passes: life" in out


def test_cli_life_only_fails_on_bad_corpus(capsys):
    rc = cli_main(["-q", "--life-only", LIFE])
    out = capsys.readouterr().out
    assert rc == 1
    for rule in LIFE_RULE_IDS:
        assert rule in out, (rule, out)


def test_cli_three_model_passes_compose(capsys):
    # the `make modelcheck` shape: all three whole-package passes in one
    # process over one shared parse
    rc = cli_main(
        ["--race-only", "--shard-only", "--life-only", RACE, SHARD, LIFE]
    )
    out = capsys.readouterr().out
    assert rc == 1
    assert "SCX401" in out and "SCX501" in out and "SCX601" in out
    assert "passes: race, shard, life" in out


def test_cli_json_covers_life_pass(capsys):
    rc = cli_main(["--json", "--life-only", LIFE])
    out = capsys.readouterr().out
    assert rc == 1
    payload = json.loads(out)
    rules = {f["rule"] for f in payload["findings"]}
    assert set(LIFE_RULE_IDS) <= rules, rules
    for finding in payload["findings"]:
        assert finding["path"] and finding["line"] > 0 and finding["message"]


# ----------------------------------------------------- costcheck (SCX7xx)

COST = os.path.join(FIXTURES, "costcheck")
COST_RULE_IDS = ["SCX701", "SCX702", "SCX703", "SCX704", "SCX705"]


@pytest.mark.parametrize("rule", COST_RULE_IDS)
def test_cost_rule_fires_exactly_on_marked_lines(rule):
    path = os.path.join(COST, f"{rule.lower()}_bad.py")
    findings = check_cost([path])
    assert findings, f"{rule} bad fixture produced no findings"
    assert {f.rule for f in findings} == {rule}
    expected = _marked_lines(path, rule)
    assert expected, f"fixture {path} has no # <- {rule} markers"
    assert sorted(f.line for f in findings) == expected, [
        f.render() for f in findings
    ]


@pytest.mark.parametrize("rule", COST_RULE_IDS)
def test_cost_rule_silent_on_clean_fixture(rule):
    findings = check_cost(
        [os.path.join(COST, f"{rule.lower()}_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_cost_real_tree_is_clean():
    # the audit contract: every SCX701-705 finding on the real tree is
    # fixed or carries a justified inline suppression (the bench
    # microbench's deliberately-unmetered setup/probe staging), and this
    # pin keeps it that way
    findings = check_cost(TREE)
    assert findings == [], [f.render() for f in findings]


def test_cost_inline_suppression(tmp_path):
    src = (
        "from sctools_tpu.ingest import upload\n\n\n"
        "def per_batch(batches, table):\n"
        "    for batch in batches:\n"
        "        upload(table, site='fix.table')  "
        "# scx-lint: disable=SCX701 -- two-batch tool, link idle\n"
    )
    path = tmp_path / "suppressed_cost.py"
    path.write_text(src)
    assert check_cost([str(path)]) == []


def test_cost_ingest_dir_is_exempt(tmp_path):
    # ingest/ OWNS the choke points: its internal forwarding of dynamic
    # caller sites is the mechanism, not a violation — the same
    # immediate-parent ownership line SCX112/SCX114 draw
    src = (
        "from sctools_tpu.obs import xprof\n\n\n"
        "def door(value, site):\n"
        "    staged = value\n"
        "    xprof.record_transfer('h2d', 8, site=str(site) + '!')\n"
        "    return staged\n"
    )
    ingest_dir = tmp_path / "ingest"
    ingest_dir.mkdir()
    (ingest_dir / "staging.py").write_text(src)
    assert check_cost([str(ingest_dir / "staging.py")]) == []
    outside = tmp_path / "staging.py"
    outside.write_text(src)
    findings = check_cost([str(outside)])
    assert {f.rule for f in findings} == {"SCX705"}
    # only the IMMEDIATE parent confers ownership
    nested = ingest_dir / "sub"
    nested.mkdir()
    (nested / "staging.py").write_text(src)
    findings = check_cost([str(nested / "staging.py")])
    assert {f.rule for f in findings} == {"SCX705"}


def test_cost_site_forwarding_crosses_helpers(tmp_path):
    # the bench probe shape: literals live at the callers of a
    # forwarding helper (two hops), inventory there, and a non-literal
    # argument is where SCX705 lands
    src = (
        "from sctools_tpu.ingest import pull\n\n\n"
        "def timed_pull(site, value):\n"
        "    return pull(value, site=site)\n\n\n"
        "def paired(site, block):\n"
        "    return timed_pull(site, block)\n\n\n"
        "def drive(block, label):\n"
        "    good = paired('fix.compact', block)\n"
        "    bad = paired('fix.' + label, block)\n"
        "    return good, bad\n"
    )
    path = tmp_path / "forwarding_cost.py"
    path.write_text(src)
    findings = check_cost([str(path)])
    assert [(f.rule, f.line) for f in findings] == [("SCX705", 14)], [
        f.render() for f in findings
    ]
    inventory = transfer_inventory([str(path)])
    assert inventory["sites"]["fix.compact"]["directions"] == ["d2h"]


def test_cost_forwarding_helper_still_held_to_record(tmp_path):
    # the forwarding excuse covers ONLY the non-literal-site branch: a
    # forwarding helper whose transfer is record=False (and nobody calls
    # record_transfer) still ships unledgered bytes — SCX705 must land
    # on the helper's own transfer
    src = (
        "from sctools_tpu.ingest import pull\n\n\n"
        "def timed_pull(site, value):\n"
        "    return pull(value, site=site, record=False)\n\n\n"
        "def drive(block):\n"
        "    return timed_pull('fix.compact', block)\n"
    )
    path = tmp_path / "forwarding_unrecorded.py"
    path.write_text(src)
    findings = check_cost([str(path)])
    assert [(f.rule, f.line) for f in findings] == [("SCX705", 5)], [
        f.render() for f in findings
    ]


def test_transfer_inventory_names_core_sites():
    inventory = transfer_inventory(TREE)
    sites = inventory["sites"]
    assert "h2d" in sites["gatherer.upload"]["directions"]
    assert "d2h" in sites["gatherer.writeback"]["directions"]
    assert "h2d" in sites["count.upload"]["directions"]
    assert "d2h" in sites["count.writeback"]["directions"]
    assert "h2d" in sites["whitelist.table"]["directions"]
    # the bench probe sites arrive through the forwarding closure
    assert "d2h" in sites["bench.wire_compact"]["directions"]
    for entry in sites.values():
        assert entry["occurrences"], entry


def test_check_transfer_sites_flags_phantoms_and_directions():
    inventory = transfer_inventory(TREE)
    clean_ledger = {
        "h2d": {"by_site": {"gatherer.upload": {"bytes": 10}}},
        "d2h": {"by_site": {"gatherer.writeback": {"bytes": 10}}},
    }
    assert check_transfer_sites(inventory, clean_ledger) == []
    phantom = {"h2d": {"by_site": {"nowhere.site": {"bytes": 1}}}}
    violations = check_transfer_sites(inventory, phantom)
    assert len(violations) == 1 and "phantom" in violations[0]
    flipped = {"h2d": {"by_site": {"gatherer.writeback": {"bytes": 1}}}}
    violations = check_transfer_sites(inventory, flipped)
    assert len(violations) == 1 and "direction" in violations[0]


def test_cli_cost_only(capsys):
    rc = cli_main(["--cost-only"] + TREE)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "passes: cost" in out


def test_cli_cost_only_fails_on_bad_corpus(capsys):
    rc = cli_main(["-q", "--cost-only", COST])
    out = capsys.readouterr().out
    assert rc == 1
    for rule in COST_RULE_IDS:
        assert rule in out, (rule, out)


def test_cli_four_model_passes_compose(capsys):
    # the `make modelcheck` shape: all four whole-package passes in one
    # process over one shared parse
    rc = cli_main(
        ["--race-only", "--shard-only", "--life-only", "--cost-only",
         RACE, SHARD, LIFE, COST]
    )
    out = capsys.readouterr().out
    assert rc == 1
    assert "SCX401" in out and "SCX501" in out
    assert "SCX601" in out and "SCX701" in out
    assert "passes: race, shard, life, cost" in out


def test_cli_json_covers_cost_pass(capsys):
    rc = cli_main(["--json", "--cost-only", COST])
    out = capsys.readouterr().out
    assert rc == 1
    payload = json.loads(out)
    rules = {f["rule"] for f in payload["findings"]}
    assert set(COST_RULE_IDS) <= rules, rules


def test_cli_emit_transfer_inventory(tmp_path, capsys):
    dest = tmp_path / "inventory.json"
    rc = cli_main(["--emit-transfer-inventory", str(dest)] + TREE)
    assert rc == 0
    payload = json.loads(dest.read_text())
    assert "gatherer.upload" in payload["sites"]
    assert payload["sites"]["gatherer.upload"]["directions"] == ["h2d"]


def test_cli_summary_reports_parse_cache(capsys):
    rc = cli_main(["--cost-only"] + TREE)
    out = capsys.readouterr().out
    assert rc == 0
    assert "parse cache:" in out


# ------------------------------------------------ astcache persistence


def test_parse_cache_persists_across_processes(tmp_path, monkeypatch):
    from sctools_tpu.analysis import astcache

    store = tmp_path / "store"
    monkeypatch.setenv(astcache.CACHE_ENV, str(store))
    target = tmp_path / "mod.py"
    target.write_text("def f(x):\n    return x + 1\n")

    before = dict(astcache.stats)
    parsed = astcache.parse_cached(str(target))
    assert parsed is not None
    assert astcache.stats["parsed"] == before["parsed"] + 1

    # same process, same content: the in-memory layer answers
    astcache.parse_cached(str(target))
    assert astcache.stats["memory_hits"] == before["memory_hits"] + 1

    # a fresh process (simulated: cleared memory layer) hits the
    # persistent content-hash store instead of reparsing
    astcache._cache.clear()
    astcache.parse_cached(str(target))
    assert astcache.stats["disk_hits"] == before["disk_hits"] + 1

    # an edit can never hit stale: new content, new hash, real parse
    target.write_text("def f(x):\n    return x + 2\n")
    astcache._cache.clear()
    source, tree = astcache.parse_cached(str(target))
    assert astcache.stats["parsed"] == before["parsed"] + 2
    assert "x + 2" in source


def test_parse_cache_disabled_by_env(tmp_path, monkeypatch):
    from sctools_tpu.analysis import astcache

    monkeypatch.setenv(astcache.CACHE_ENV, "0")
    target = tmp_path / "mod.py"
    target.write_text("VALUE = 1\n")
    before = astcache.stats["parsed"]
    astcache.parse_cached(str(target))
    astcache._cache.clear()
    astcache.parse_cached(str(target))
    assert astcache.stats["parsed"] == before + 2  # no store, reparses


def test_parse_cache_survives_corrupt_store_entry(tmp_path, monkeypatch):
    from sctools_tpu.analysis import astcache

    store = tmp_path / "store"
    monkeypatch.setenv(astcache.CACHE_ENV, str(store))
    target = tmp_path / "mod.py"
    target.write_text("VALUE = 3\n")
    astcache.parse_cached(str(target))
    entries = list(store.glob("*.pkl"))
    assert entries
    entries[0].write_bytes(b"corrupt")
    astcache._cache.clear()
    before = astcache.stats["parsed"]
    parsed = astcache.parse_cached(str(target))
    assert parsed is not None and astcache.stats["parsed"] == before + 1


# ----------------------------------------------------- retune (autotuner)


def _retune_registry(run_dir, record_mean=300, entity_mean=20,
                     signature=None):
    registry = {
        "version": 1,
        "worker": "w0",
        "sites": {
            "metrics.compute_entity_metrics": {
                "calls": 40, "compiles": 1, "retraces": 0,
                "compile_s": 1.0, "dispatches": 40,
                "real_rows": record_mean * 40, "padded_rows": 4096 * 40,
                "signatures": {
                    signature
                    or "(int32[512], bool[512]) {kind='cell'}": 40
                },
                "retrace_signatures": [],
            },
            "metrics.compact_results_wire": {
                "calls": 40, "compiles": 1, "retraces": 0,
                "compile_s": 0.2, "dispatches": 40,
                "real_rows": entity_mean * 40, "padded_rows": 64 * 40,
                "signatures": {"(int32[14,64])": 40},
                "retrace_signatures": [],
            },
        },
        "declared_sites": [
            "metrics.compute_entity_metrics",
            "metrics.compact_results_wire",
        ],
        "ledger": {},
        "memory": {},
    }
    with open(os.path.join(run_dir, "xprof.w0.json"), "w") as f:
        json.dump(registry, f)


@pytest.fixture
def retune_tree(tmp_path):
    """A disposable copy of the real tree the autotuner may rewrite."""
    import shutil

    tree = tmp_path / "tree"
    tree.mkdir()
    shutil.copytree(
        os.path.join(REPO, "sctools_tpu"), str(tree / "sctools_tpu"),
        ignore=shutil.ignore_patterns(
            "__pycache__", "*.so", "*.o", "*.buildhost"
        ),
    )
    shutil.copy(os.path.join(REPO, "bench.py"), str(tree / "bench.py"))
    shutil.copy(
        os.path.join(REPO, "__graft_entry__.py"),
        str(tree / "__graft_entry__.py"),
    )
    return tree


def _tree_paths(tree):
    return [
        str(tree / "sctools_tpu"),
        str(tree / "bench.py"),
        str(tree / "__graft_entry__.py"),
    ]


def test_retune_roundtrip_rewrites_and_gates(tmp_path, retune_tree):
    # recorded registry -> derived floors -> rewrite -> shardcheck green
    # -> contract covers observed signatures -> occupancy improves
    from sctools_tpu.analysis import retune as retune_mod

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    _retune_registry(str(run_dir))
    segments = retune_tree / "sctools_tpu" / "ops" / "segments.py"
    assert retune_mod.read_constants(str(segments)) == {
        "RECORD_BUCKET_MIN": 4096, "ENTITY_BUCKET_MIN": 64,
    }
    lines = []
    code, report = retune_mod.retune(
        str(run_dir), _tree_paths(retune_tree), out=lines.append
    )
    assert code == 0, lines
    assert report["applied"] is True
    assert report["gates"]["shardcheck"]["ok"]
    assert report["gates"]["shape_contract"]["ok"]
    written = retune_mod.read_constants(str(segments))
    # mean 300 real rows -> smallest pow2 is 512; mean 20 entities -> 32
    assert written == {"RECORD_BUCKET_MIN": 512, "ENTITY_BUCKET_MIN": 32}
    record = report["constants"]["RECORD_BUCKET_MIN"]
    assert record["projected_occupancy"] > record["observed_occupancy"]

    # the pinned floor is live behavior: a small dispatch pads an order
    # of magnitude tighter under the autotuned constant
    from sctools_tpu.ops import segments as seg

    assert seg.bucket_size(300) == 4096  # repo pin unchanged
    assert seg.bucket_size(300, minimum=written["RECORD_BUCKET_MIN"]) == 512


def test_retune_never_raises_a_floor(tmp_path, retune_tree):
    # traffic whose mean dispatch exceeds the pin must leave it alone:
    # raising a floor can only lower occupancy
    from sctools_tpu.analysis import retune as retune_mod

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    _retune_registry(str(run_dir), record_mean=500000, entity_mean=4000)
    lines = []
    code, report = retune_mod.retune(
        str(run_dir), _tree_paths(retune_tree), out=lines.append
    )
    assert code == 0
    assert report["applied"] is False and report["changed"] == {}
    segments = retune_tree / "sctools_tpu" / "ops" / "segments.py"
    assert retune_mod.read_constants(str(segments)) == {
        "RECORD_BUCKET_MIN": 4096, "ENTITY_BUCKET_MIN": 64,
    }


def test_retune_clamps_to_hard_floor(tmp_path, retune_tree):
    from sctools_tpu.analysis import retune as retune_mod

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    _retune_registry(str(run_dir), record_mean=3, entity_mean=1)
    code, report = retune_mod.retune(
        str(run_dir), _tree_paths(retune_tree), out=lambda s: None
    )
    assert code == 0
    written = retune_mod.read_constants(
        str(retune_tree / "sctools_tpu" / "ops" / "segments.py")
    )
    assert written == {
        "RECORD_BUCKET_MIN": retune_mod.HARD_FLOORS["RECORD_BUCKET_MIN"],
        "ENTITY_BUCKET_MIN": retune_mod.HARD_FLOORS["ENTITY_BUCKET_MIN"],
    }


def test_retune_gate_rejects_uncovered_signature(tmp_path, retune_tree):
    # an observed signature the regenerated contract cannot admit must
    # refuse the edit and restore the file byte-for-byte
    from sctools_tpu.analysis import retune as retune_mod

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    _retune_registry(
        str(run_dir), signature="(int32[12345], bool[12345])"
    )
    segments = retune_tree / "sctools_tpu" / "ops" / "segments.py"
    original = segments.read_text()
    lines = []
    code, report = retune_mod.retune(
        str(run_dir), _tree_paths(retune_tree), out=lines.append
    )
    assert code == 5, lines
    assert report["applied"] is False
    assert not report["gates"]["shape_contract"]["ok"]
    assert segments.read_text() == original


def test_retune_dry_run_writes_nothing(tmp_path, retune_tree):
    from sctools_tpu.analysis import retune as retune_mod

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    _retune_registry(str(run_dir))
    segments = retune_tree / "sctools_tpu" / "ops" / "segments.py"
    original = segments.read_text()
    code, report = retune_mod.retune(
        str(run_dir), _tree_paths(retune_tree), apply=False,
        out=lambda s: None,
    )
    assert code == 0
    assert report["applied"] is False
    assert report["changed"] == {
        "RECORD_BUCKET_MIN": 512, "ENTITY_BUCKET_MIN": 32,
    }
    assert segments.read_text() == original


def test_retune_without_registries_fails_loudly(tmp_path, retune_tree):
    from sctools_tpu.analysis import retune as retune_mod

    empty = tmp_path / "empty"
    empty.mkdir()
    code, _ = retune_mod.retune(
        str(empty), _tree_paths(retune_tree), out=lambda s: None
    )
    assert code == 2


# ----------------------------------------------------- meshcheck (SCX8xx)

MESH = os.path.join(FIXTURES, "meshcheck")
MESH_RULE_IDS = ["SCX801", "SCX802", "SCX803", "SCX804", "SCX805"]


@pytest.mark.parametrize("rule", MESH_RULE_IDS)
def test_mesh_rule_fires_exactly_on_marked_lines(rule):
    path = os.path.join(MESH, f"{rule.lower()}_bad.py")
    findings = check_mesh([path])
    assert findings, f"{rule} bad fixture produced no findings"
    assert {f.rule for f in findings} == {rule}
    expected = _marked_lines(path, rule)
    assert expected, f"fixture {path} has no # <- {rule} markers"
    assert sorted(f.line for f in findings) == expected, [
        f.render() for f in findings
    ]


@pytest.mark.parametrize("rule", MESH_RULE_IDS)
def test_mesh_rule_silent_on_clean_fixture(rule):
    findings = check_mesh(
        [os.path.join(MESH, f"{rule.lower()}_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_mesh_real_tree_is_clean():
    # the audit contract: every SCX801-805 finding on the real tree is
    # fixed or carries a justified inline suppression (the graft dry
    # run's deliberately pinned 2-slice hybrid leg), and this pin keeps
    # it that way — the precondition for the on-device collective merge
    findings = check_mesh(TREE)
    assert findings == [], [f.render() for f in findings]


def test_mesh_inline_suppression(tmp_path):
    src = (
        "def shard_for_mesh(cols, mesh):\n"
        "    n_shards = 8  "
        "# scx-lint: disable=SCX804 -- fixture rig pins the bench topology\n"
        "    return n_shards\n"
    )
    path = tmp_path / "suppressed_mesh.py"
    path.write_text(src)
    assert check_mesh([str(path)]) == []


def test_mesh_collective_module_is_mechanism_exempt(tmp_path):
    # the choke-point wrappers hold the raw jax.lax calls every caller
    # forwards to; their bodies must not inventory as collective issues
    # (a builder-shaped caller would otherwise inherit phantom findings)
    src = (
        "import functools\n\n"
        "import jax\n"
        "from jax.sharding import PartitionSpec as P\n\n"
        "from sctools_tpu.platform import shard_map\n\n"
        "AXIS = 'shard'\n\n\n"
        "def build(mesh, combine):\n"
        "    @functools.partial(\n"
        "        shard_map, mesh=mesh, in_specs=(P(AXIS),),"
        " out_specs=P(AXIS),\n"
        "    )\n"
        "    def step(block):\n"
        "        if combine == 'sum':\n"
        "            out = jax.lax.psum(block, AXIS)\n"
        "        else:\n"
        "            out = jax.lax.all_gather(block, AXIS).sum(axis=0)\n"
        "        return out\n\n"
        "    return step\n"
    )
    plain = tmp_path / "caller.py"
    plain.write_text(src)
    assert {f.rule for f in check_mesh([str(plain)])} == {"SCX802"}
    # the same text in a module NAMED collective.py is the mechanism
    mech = tmp_path / "collective.py"
    mech.write_text(src)
    assert check_mesh([str(mech)]) == []


def test_mesh_collective_wrappers_are_recognized(tmp_path):
    # collectives issued through the parallel.collective choke point are
    # the same vocabulary as bare jax.lax for every SCX8xx rule
    src = (
        "import functools\n\n"
        "from jax.sharding import PartitionSpec as P\n\n"
        "from sctools_tpu.parallel.collective import all_gather, psum\n"
        "from sctools_tpu.platform import shard_map\n\n"
        "AXIS = 'shard'\n\n\n"
        "def build(mesh, combine):\n"
        "    @functools.partial(\n"
        "        shard_map, mesh=mesh, in_specs=(P(AXIS),),"
        " out_specs=P(AXIS),\n"
        "    )\n"
        "    def step(block):\n"
        "        if combine == 'sum':\n"
        "            out = psum(block, AXIS)\n"
        "        else:\n"
        "            out = all_gather(block, AXIS).sum(axis=0)\n"
        "        return out\n\n"
        "    return step\n"
    )
    path = tmp_path / "wrapped.py"
    path.write_text(src)
    findings = check_mesh([str(path)])
    assert {f.rule for f in findings} == {"SCX802"}, [
        f.render() for f in findings
    ]


def test_collective_schedule_names_real_regions():
    from sctools_tpu.analysis import build_collective_schedule

    schedule = build_collective_schedule(TREE)
    pairs = {tuple(p) for p in schedule["collectives"]}
    assert ("all_to_all", "*") in pairs
    assert ("all_gather", "*") in pairs
    regions = set(schedule["regions"])
    assert "sctools_tpu.parallel.metrics._build_distributed_step.step" in (
        regions
    )
    assert "sctools_tpu.parallel.sort._build_sample_sort.run" in regions
    assert (
        "sctools_tpu.parallel.metrics.reshard_by_key"
        in schedule["computations"]
    )
    assert set(schedule["axis_universe"]) >= {"shard", "dcn"}


def test_cli_mesh_only(capsys):
    rc = cli_main(["--mesh-only"] + TREE)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "passes: mesh" in out


def test_cli_mesh_only_fails_on_bad_corpus(capsys):
    rc = cli_main(["-q", "--mesh-only", MESH])
    out = capsys.readouterr().out
    assert rc == 1
    for rule in MESH_RULE_IDS:
        assert rule in out, (rule, out)


def test_cli_six_model_passes_compose(capsys):
    # the `make modelcheck` shape: all six whole-package passes in one
    # process over one shared parse
    aot = os.path.join(FIXTURES, "aotcheck")
    rc = cli_main(
        ["--race-only", "--shard-only", "--life-only", "--cost-only",
         "--mesh-only", "--aot-only", RACE, SHARD, LIFE, COST, MESH, aot]
    )
    out = capsys.readouterr().out
    assert rc == 1
    assert "SCX401" in out and "SCX501" in out
    assert "SCX601" in out and "SCX701" in out and "SCX801" in out
    assert "SCX901" in out
    assert "passes: race, shard, life, cost, mesh, aot" in out


def test_cli_json_covers_mesh_pass(capsys):
    rc = cli_main(["--json", "--mesh-only", MESH])
    out = capsys.readouterr().out
    assert rc == 1
    payload = json.loads(out)
    rules = {f["rule"] for f in payload["findings"]}
    assert set(MESH_RULE_IDS) <= rules, rules


def test_cli_emit_collective_schedule(tmp_path, capsys):
    dest = tmp_path / "schedule.json"
    rc = cli_main(["--emit-collective-schedule", str(dest)] + TREE)
    capsys.readouterr()
    assert rc == 0
    with open(dest, encoding="utf-8") as f:
        schedule = json.load(f)
    assert schedule["collectives"] and schedule["regions"]


# ------------------------------------------- runtime collective witness


def test_mesh_witness_off_is_noop(monkeypatch):
    from sctools_tpu.analysis import meshwitness

    monkeypatch.delenv(meshwitness.ENV_FLAG, raising=False)
    meshwitness.reset()
    meshwitness.record_collective("psum", "shard", (4,), "int32", 16)
    snap = meshwitness.snapshot()
    assert snap["sequence"] == [] and snap["counts"] == {}


def test_mesh_witness_records_regions_and_dedupes(monkeypatch):
    from sctools_tpu.analysis import meshwitness

    monkeypatch.setenv(meshwitness.ENV_FLAG, "1")
    monkeypatch.delenv(meshwitness.ENV_SCHEDULE, raising=False)
    meshwitness.reset()
    for _ in range(2):
        with meshwitness.region("fix.step"):
            meshwitness.record_collective("psum", "shard", (4,), "int32", 16)
            meshwitness.record_collective(
                "all_gather", ("dcn", "shard"), (4, 2), "int32", 32
            )
    snap = meshwitness.snapshot()
    assert snap["violations"] == []
    rows = snap["schedules"]["fix.step"]
    assert len(rows) == 1 and rows[0]["count"] == 2
    assert [e["name"] for e in rows[0]["entries"]] == ["psum", "all_gather"]
    assert rows[0]["entries"][1]["axis"] == "dcn+shard"
    assert snap["counts"] == {"psum": 2, "all_gather": 2}
    assert snap["bytes"] == {"psum": 32, "all_gather": 64}
    # a DIFFERENT sequence for the same region is kept separately
    with meshwitness.region("fix.step"):
        meshwitness.record_collective("psum", "shard", (4,), "int32", 16)
    assert len(meshwitness.snapshot()["schedules"]["fix.step"]) == 2
    meshwitness.reset()


def test_mesh_witness_flags_unscheduled_collective(tmp_path, monkeypatch):
    from sctools_tpu.analysis import meshwitness

    schedule = tmp_path / "schedule.json"
    schedule.write_text(json.dumps({"collectives": [["psum", "shard"]]}))
    monkeypatch.setenv(meshwitness.ENV_FLAG, "1")
    monkeypatch.setenv(meshwitness.ENV_SCHEDULE, str(schedule))
    meshwitness.reset()
    with meshwitness.region("fix.step"):
        meshwitness.record_collective("psum", "shard", (4,), "int32", 16)
        meshwitness.record_collective("ppermute", "shard", (4,), "int32", 16)
    kinds = [v["kind"] for v in meshwitness.violations()]
    assert kinds == ["unscheduled-collective"]
    meshwitness.reset()


def test_mesh_witness_flags_outside_region(monkeypatch):
    from sctools_tpu.analysis import meshwitness

    monkeypatch.setenv(meshwitness.ENV_FLAG, "1")
    monkeypatch.delenv(meshwitness.ENV_SCHEDULE, raising=False)
    meshwitness.reset()
    meshwitness.record_collective("psum", "shard", (4,), "int32", 16)
    kinds = [v["kind"] for v in meshwitness.violations()]
    assert kinds == ["outside-region"]
    meshwitness.reset()


def test_mesh_witness_dump_roundtrip(tmp_path, monkeypatch):
    from sctools_tpu.analysis import meshwitness

    monkeypatch.setenv(meshwitness.ENV_FLAG, "1")
    monkeypatch.delenv(meshwitness.ENV_SCHEDULE, raising=False)
    meshwitness.reset()
    with meshwitness.region("fix.step"):
        meshwitness.record_collective("psum", "shard", (8,), "float32", 32)
    dest = tmp_path / "mesh.p0.json"
    assert meshwitness.dump(str(dest)) == str(dest)
    loaded = meshwitness.load_dumps(str(tmp_path))
    assert set(loaded) == {"p0"}
    assert loaded["p0"]["counts"] == {"psum": 1}
    assert loaded["p0"]["violations"] == []
    meshwitness.reset()

# ------------------------------------------------------ aotcheck (SCX9xx)

AOT = os.path.join(FIXTURES, "aotcheck")
AOT_RULE_IDS = ["SCX901", "SCX902", "SCX903", "SCX904", "SCX905"]
COMMITTED_MANIFEST = os.path.join(
    REPO, "sctools_tpu", "serve", "aot_manifest.json"
)


@pytest.mark.parametrize("rule", AOT_RULE_IDS)
def test_aot_rule_fires_exactly_on_marked_lines(rule):
    path = os.path.join(AOT, f"{rule.lower()}_bad.py")
    findings = check_aot([path])
    assert findings, f"{rule} bad fixture produced no findings"
    assert {f.rule for f in findings} == {rule}
    expected = _marked_lines(path, rule)
    assert expected, f"fixture {path} has no # <- {rule} markers"
    assert sorted(f.line for f in findings) == expected, [
        f.render() for f in findings
    ]


@pytest.mark.parametrize("rule", AOT_RULE_IDS)
def test_aot_rule_silent_on_clean_fixture(rule):
    findings = check_aot(
        [os.path.join(AOT, f"{rule.lower()}_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_aot_real_tree_is_clean():
    # the audit contract: every SCX901-905 finding on the real tree is
    # fixed or carries a justified inline suppression — the precondition
    # for the resident serving plane admitting traffic at all
    findings = check_aot(TREE)
    assert findings == [], [f.render() for f in findings]


def test_aot_inline_suppression(tmp_path):
    src = (
        "import os\n\n"
        "from sctools_tpu.serve.api import serve_entry\n\n\n"
        "@serve_entry\n"
        "def handle(request):\n"
        "    mode = os.environ.get('MODE')  "
        "# scx-lint: disable=SCX903 -- pinned at spawn, never varies\n"
        "    return mode\n"
    )
    path = tmp_path / "suppressed_serve.py"
    path.write_text(src)
    assert check_aot([str(path)]) == []


def test_aot_manifest_build_names_real_universe():
    manifest = build_aot_manifest(TREE)
    assert manifest["version"] == 1
    assert (
        "sctools_tpu.serve.engine.ServeWorker.serve_forever"
        in manifest["serve_entries"]
    )
    assert manifest["contract_hash"] == contract_hash(manifest["contract"])
    sites = manifest["sites"]
    assert sites, "empty site universe"
    assert any(entry["precompile"] for entry in sites.values())
    for entry in sites.values():
        assert set(entry) >= {
            "dims", "module", "axes", "sharded", "static_argnames",
            "serve_reachable", "precompile",
        }


def test_aot_manifest_validates_fresh_and_rejects_tamper():
    manifest = build_aot_manifest(TREE)
    assert validate_manifest(manifest, TREE) == []
    tampered = dict(manifest)
    tampered["contract_hash"] = "0" * 64
    problems = validate_manifest(tampered, TREE)
    assert problems and any("hash" in p for p in problems), problems


def test_aot_manifest_staleness_detected():
    # a manifest certified for one tree must not validate against a tree
    # with a different shape contract
    manifest = build_aot_manifest(TREE)
    problems = validate_manifest(manifest, [AOT])
    assert problems and any(
        "--emit-aot-manifest" in p for p in problems
    ), problems


def test_committed_manifest_is_fresh():
    # the staleness gate `make aotcheck` runs, pinned as a test: the
    # manifest committed beside the serve package must match the live
    # tree's shape contract (regenerate with --emit-aot-manifest)
    with open(COMMITTED_MANIFEST, encoding="utf-8") as f:
        manifest = json.load(f)
    assert validate_manifest(manifest, TREE) == []


def test_cli_aot_only(capsys):
    rc = cli_main(["--aot-only"] + TREE)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "passes: aot" in out


def test_cli_aot_only_fails_on_bad_corpus(capsys):
    rc = cli_main(["-q", "--aot-only", AOT])
    out = capsys.readouterr().out
    assert rc == 1
    for rule in AOT_RULE_IDS:
        assert rule in out, (rule, out)


def test_cli_json_covers_aot_pass(capsys):
    rc = cli_main(["--json", "--aot-only", AOT])
    out = capsys.readouterr().out
    assert rc == 1
    payload = json.loads(out)
    rules = {f["rule"] for f in payload["findings"]}
    assert set(AOT_RULE_IDS) <= rules, rules


def test_cli_emit_aot_manifest(tmp_path, capsys):
    dest = tmp_path / "manifest.json"
    rc = cli_main(["--emit-aot-manifest", str(dest)] + TREE)
    capsys.readouterr()
    assert rc == 0
    with open(dest, encoding="utf-8") as f:
        manifest = json.load(f)
    assert manifest["sites"] and manifest["serve_entries"]
    assert validate_manifest(manifest, TREE) == []


def test_cli_aot_manifest_gate(tmp_path, capsys):
    dest = tmp_path / "manifest.json"
    assert cli_main(["--emit-aot-manifest", str(dest)] + TREE) == 0
    capsys.readouterr()
    # fresh manifest passes the gate
    rc = cli_main(["--aot-only", "--aot-manifest", str(dest)] + TREE)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "manifest" in out
    # a tampered manifest fails it with an scx-aot message
    with open(dest, encoding="utf-8") as f:
        manifest = json.load(f)
    manifest["contract_hash"] = "0" * 64
    with open(dest, "w", encoding="utf-8") as f:
        json.dump(manifest, f)
    rc = cli_main(["--aot-only", "--aot-manifest", str(dest)] + TREE)
    captured = capsys.readouterr()
    assert rc == 1
    assert "scx-aot" in captured.err
    # an unreadable manifest path also gates
    rc = cli_main(
        ["--aot-only", "--aot-manifest", str(tmp_path / "missing.json")]
        + TREE
    )
    captured = capsys.readouterr()
    assert rc == 1
    assert "cannot read manifest" in captured.err
