"""CLI-level integration tests: every console entry point driven via
``Platform.method(args_list)`` against generated files, outputs re-opened and
asserted (the reference's test style, test_entrypoints.py:15-307)."""

import gzip
import random
import textwrap

import numpy as np
import pytest

from sctools_tpu import platform
from sctools_tpu.count import CountMatrix
from sctools_tpu.io.sam import AlignmentReader

from helpers import make_header, make_record, write_bam, write_fastq, write_gtf

RNG = random.Random(11)
CELLS = ["".join(RNG.choice("ACGT") for _ in range(16)) for _ in range(6)]
GENES = ["ACTB", "GAPDH", "MT-CO1"]


def _tagged_records(n=120, header=None):
    header = header or make_header()
    records = []
    for i in range(n):
        cb = RNG.choice(CELLS)
        records.append(
            make_record(
                name=f"q{i:05d}",
                cb=cb, cr=cb, cy="I" * 16,
                ub="".join(RNG.choice("ACGT") for _ in range(10)), uy="I" * 10,
                ge=RNG.choice(GENES), xf="CODING", nh=1,
                pos=RNG.randrange(5000), header=header,
            )
        )
    return records, header


@pytest.fixture(scope="module")
def tagged_bam(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("entry")
    records, header = _tagged_records()
    return write_bam(tmp / "tagged.bam", records, header)


@pytest.fixture(scope="module")
def annotation_gtf(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("gtf")
    return write_gtf(
        tmp / "genes.gtf",
        [
            {"gene_id": f"ENSG{i}", "gene_name": g, "start": 1 + i * 2000,
             "end": 1000 + i * 2000}
            for i, g in enumerate(GENES)
        ],
    )


# ---------------------------------------------------------------- attach

def test_attach_10x_barcodes(tmp_path):
    # r1 carries barcode+umi; u2 is the unaligned cDNA bam
    r1 = [
        ("r1", CELLS[0] + "ACGTACGTAC" + "TT", "I" * 28),
        ("r2", CELLS[1] + "CCCCCCCCCC" + "GG", "I" * 28),
    ]
    r1_path = write_fastq(tmp_path / "r1.fastq", r1)
    header = make_header()
    u2 = write_bam(
        tmp_path / "u2.bam",
        [make_record(name="r1", unmapped=True, header=header),
         make_record(name="r2", unmapped=True, header=header)],
        header,
    )
    out = str(tmp_path / "tagged.bam")
    rc = platform.TenXV2.attach_barcodes(["--r1", r1_path, "--u2", u2, "-o", out])
    assert rc == 0
    with AlignmentReader(out) as f:
        records = list(f)
    assert records[0].get_tag("CR") == CELLS[0]
    assert records[0].get_tag("UR") == "ACGTACGTAC"
    assert records[1].get_tag("CR") == CELLS[1]


def test_attach_10x_barcodes_with_whitelist_correction(tmp_path):
    whitelist = tmp_path / "whitelist.txt"
    whitelist.write_text("\n".join(CELLS) + "\n")
    mutated = ("T" if CELLS[0][0] != "T" else "G") + CELLS[0][1:]
    r1_path = write_fastq(
        tmp_path / "r1.fastq", [("r1", mutated + "ACGTACGTAC" + "TT", "I" * 28)]
    )
    header = make_header()
    u2 = write_bam(
        tmp_path / "u2.bam", [make_record(name="r1", unmapped=True, header=header)],
        header,
    )
    out = str(tmp_path / "tagged.bam")
    rc = platform.TenXV2.attach_barcodes(
        ["--r1", r1_path, "--u2", u2, "-o", out, "-w", str(whitelist)]
    )
    assert rc == 0
    with AlignmentReader(out) as f:
        record = next(iter(f))
    assert record.get_tag("CR") == mutated
    assert record.get_tag("CB") == CELLS[0]  # corrected to whitelist


def test_attach_barcodes_custom_geometry(tmp_path):
    # cell barcode at [2, 10), molecule at [10, 14)
    cell, umi = "ACGTACGT", "TTTT"
    r1_path = write_fastq(tmp_path / "r1.fastq", [("r1", "NN" + cell + umi, "I" * 14)])
    header = make_header()
    u2 = write_bam(
        tmp_path / "u2.bam", [make_record(name="r1", unmapped=True, header=header)],
        header,
    )
    out = str(tmp_path / "tagged.bam")
    rc = platform.BarcodePlatform.attach_barcodes(
        [
            "--r1", r1_path, "--u2", u2, "-o", out,
            "--cell-barcode-start-position", "2",
            "--cell-barcode-length", "8",
            "--molecule-barcode-start-position", "10",
            "--molecule-barcode-length", "4",
        ]
    )
    assert rc == 0
    with AlignmentReader(out) as f:
        record = next(iter(f))
    assert record.get_tag("CR") == cell
    assert record.get_tag("UR") == umi


def test_attach_barcodes_rejects_length_without_position(tmp_path):
    with pytest.raises((SystemExit, Exception)):
        platform.BarcodePlatform.attach_barcodes(
            ["--r1", "x", "--u2", "y", "-o", "z", "--cell-barcode-length", "8"]
        )


def test_attach_barcodes_rejects_overlapping_cell_and_molecule():
    import argparse

    with pytest.raises(argparse.ArgumentTypeError):
        platform.BarcodePlatform.attach_barcodes(
            [
                "--r1", "x", "--u2", "y", "-o", "z",
                "--cell-barcode-start-position", "0",
                "--cell-barcode-length", "16",
                "--molecule-barcode-start-position", "8",
                "--molecule-barcode-length", "10",
            ]
        )


def test_attach_barcodes_rejects_sample_barcode_without_i1():
    import argparse

    with pytest.raises(argparse.ArgumentTypeError):
        platform.BarcodePlatform.attach_barcodes(
            [
                "--r1", "x", "--u2", "y", "-o", "z",
                "--sample-barcode-start-position", "0",
                "--sample-barcode-length", "8",
            ]
        )


# ---------------------------------------------------------------- sort / verify

def test_tag_sort_and_verify(tmp_path, tagged_bam):
    out = str(tmp_path / "sorted.bam")
    rc = platform.GenericPlatform.tag_sort_bam(
        ["-i", tagged_bam, "-o", out, "-t", "CB", "UB", "GE"]
    )
    assert rc == 0
    rc = platform.GenericPlatform.verify_bam_sort(
        ["-i", out, "-t", "CB", "UB", "GE"]
    )
    assert rc == 0


def test_verify_unsorted_raises(tagged_bam):
    from sctools_tpu.bam import SortError

    with pytest.raises(SortError):
        platform.GenericPlatform.verify_bam_sort(
            ["-i", tagged_bam, "-t", "CB", "UB", "GE"]
        )


# ---------------------------------------------------------------- split

def test_split_bam(tmp_path, tagged_bam):
    prefix = str(tmp_path / "chunk")
    rc = platform.GenericPlatform.split_bam(
        ["-b", tagged_bam, "-p", prefix, "-s", "0.0005", "-t", "CB"]
    )
    assert rc == 0
    import glob

    chunks = sorted(glob.glob(prefix + "*"))
    assert len(chunks) > 1
    # barcode partition is disjoint across chunks
    seen = {}
    total = 0
    for chunk in chunks:
        with AlignmentReader(chunk) as f:
            for record in f:
                total += 1
                cb = record.get_tag("CB")
                assert seen.setdefault(cb, chunk) == chunk
    assert total == 120


# ---------------------------------------------------------------- metrics

def test_calculate_and_merge_cell_metrics(tmp_path, tagged_bam, annotation_gtf):
    sorted_bam = str(tmp_path / "sorted.bam")
    platform.GenericPlatform.tag_sort_bam(
        ["-i", tagged_bam, "-o", sorted_bam, "-t", "CB", "UB", "GE"]
    )
    stem = str(tmp_path / "cell_metrics")
    rc = platform.GenericPlatform.calculate_cell_metrics(
        ["-i", sorted_bam, "-o", stem, "-a", annotation_gtf]
    )
    assert rc == 0
    lines = gzip.open(stem + ".csv.gz", "rt").read().strip().splitlines()
    assert len(lines) == 1 + len(CELLS)

    merged = str(tmp_path / "merged_cell")
    rc = platform.GenericPlatform.merge_cell_metrics(
        [stem + ".csv.gz", stem + ".csv.gz", "-o", merged]
    )
    assert rc == 0
    merged_lines = gzip.open(merged + ".csv.gz", "rt").read().strip().splitlines()
    assert len(merged_lines) == 1 + 2 * len(CELLS)


def test_calculate_and_merge_gene_metrics(tmp_path, tagged_bam):
    sorted_bam = str(tmp_path / "gene_sorted.bam")
    platform.GenericPlatform.tag_sort_bam(
        ["-i", tagged_bam, "-o", sorted_bam, "-t", "GE", "CB", "UB"]
    )
    stem = str(tmp_path / "gene_metrics")
    rc = platform.GenericPlatform.calculate_gene_metrics(["-i", sorted_bam, "-o", stem])
    assert rc == 0
    lines = gzip.open(stem + ".csv.gz", "rt").read().strip().splitlines()
    assert len(lines) == 1 + len(GENES)

    merged = str(tmp_path / "merged_gene")
    rc = platform.GenericPlatform.merge_gene_metrics(
        [stem + ".csv.gz", stem + ".csv.gz", "-o", merged]
    )
    assert rc == 0
    merged_lines = gzip.open(merged + ".csv.gz", "rt").read().strip().splitlines()
    assert len(merged_lines) == 1 + len(GENES)


# ---------------------------------------------------------------- counting

def test_count_matrix_and_merge(tmp_path, tagged_bam, annotation_gtf):
    prefix = str(tmp_path / "counts")
    rc = platform.GenericPlatform.bam_to_count_matrix(
        ["-b", tagged_bam, "-o", prefix, "-a", annotation_gtf]
    )
    assert rc == 0
    cm = CountMatrix.load(prefix)
    assert cm.matrix.shape == (len(CELLS), len(GENES))
    assert int(cm.matrix.sum()) == 120  # all umis unique in fixture

    # --devices: the sharded kernel through the CLI == single-device
    mesh_prefix = str(tmp_path / "counts_mesh")
    rc = platform.GenericPlatform.bam_to_count_matrix(
        ["-b", tagged_bam, "-o", mesh_prefix, "-a", annotation_gtf,
         "--devices", "8"]
    )
    assert rc == 0
    mesh_cm = CountMatrix.load(mesh_prefix)
    np.testing.assert_array_equal(mesh_cm.row_index, cm.row_index)
    assert (mesh_cm.matrix != cm.matrix).nnz == 0

    merged_prefix = str(tmp_path / "merged_counts")
    rc = platform.GenericPlatform.merge_count_matrices(
        ["-i", prefix, prefix, "-o", merged_prefix]
    )
    assert rc == 0
    merged = CountMatrix.load(merged_prefix)
    assert merged.matrix.shape == (2 * len(CELLS), len(GENES))


# ---------------------------------------------------------------- qc grouping

def test_group_qc_outputs(tmp_path):
    picard = tmp_path / "cellA_qc.duplication_metrics.txt"
    picard.write_text(textwrap.dedent("""\
        ## htsjdk.samtools.metrics.StringHeader
        # MarkDuplicates INPUT=x.bam
        ## METRICS CLASS\tpicard.sam.DuplicationMetrics
        LIBRARY\tREAD_PAIRS_EXAMINED\tPERCENT_DUPLICATION
        lib1\t400\t0.25
        """))
    out = str(tmp_path / "qc")
    rc = platform.GenericPlatform.group_qc_outputs(
        ["-f", str(picard), "-o", out, "-t", "Picard"]
    )
    assert rc == 0
    assert (tmp_path / "qc.csv").exists()


def test_cli_flags_reference_is_current():
    """docs/cli_flags.md == the generator's output, whole file.

    Whole-file equality (not per-command substrings) so stale sections of
    removed commands cannot linger; the command list derives from
    pyproject.toml, so a new console script missing from the page fails
    here too (round-5 VERDICT item 8).
    """
    import importlib.util
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "generate_cli_reference",
        os.path.join(repo, "docs", "generate_cli_reference.py"),
    )
    gen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gen)
    if sys.version_info[:2] != gen.PINNED_PYTHON:
        pytest.skip(
            "argparse help formatting varies across CPython minors; the "
            f"page is pinned to {gen.PINNED_PYTHON}"
        )
    with open(os.path.join(repo, "docs", "cli_flags.md")) as f:
        committed = f.read()
    assert gen.render_page() == committed, (
        "docs/cli_flags.md drifted from the live parsers; rerun "
        "python docs/generate_cli_reference.py (make docs)"
    )
